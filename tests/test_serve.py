"""Serve tests: deployments, composition, autoscaling, HTTP proxy (ref
analogs: python/ray/serve/tests/)."""

import json
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture
def serve_cluster(local_cluster):
    yield local_cluster
    serve.shutdown()


def test_basic_class_deployment(serve_cluster):
    @serve.deployment
    class Greeter:
        def __init__(self, greeting):
            self.greeting = greeting

        def __call__(self, name):
            return f"{self.greeting}, {name}!"

    handle = serve.run(Greeter.bind("Hello"), name="greet")
    assert handle.remote("TPU").result(timeout=30) == "Hello, TPU!"


def test_function_deployment_and_methods(serve_cluster):
    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind(), name="double")
    assert handle.remote(21).result(timeout=30) == 42

    @serve.deployment
    class Calc:
        def add(self, a, b):
            return a + b

        async def sub(self, a, b):
            return a - b

    h = serve.run(Calc.bind(), name="calc")
    assert h.options(method_name="add").remote(2, 3).result(timeout=30) == 5
    assert h.options(method_name="sub").remote(9, 4).result(timeout=30) == 5


def test_composition(serve_cluster):
    @serve.deployment
    class Preprocess:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Model:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            y = self.pre.remote(x).result(timeout=30)
            return y * 10

    handle = serve.run(Model.bind(Preprocess.bind()), name="composed")
    assert handle.remote(4).result(timeout=30) == 50


def test_multiple_replicas_spread_load(serve_cluster):
    @serve.deployment(num_replicas=3)
    class Who:
        def __call__(self, _):
            import os

            return os.getpid()

    handle = serve.run(Who.bind(), name="who")
    pids = {handle.remote(None).result(timeout=30) for _ in range(24)}
    assert len(pids) >= 2  # p2c spreads across replicas


def test_http_proxy(serve_cluster):
    port = serve.start(http_port=0)

    @serve.deployment
    class Echo:
        def __call__(self, payload):
            return {"echo": payload}

    serve.run(Echo.bind(), name="echo")
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/echo",
        data=json.dumps({"msg": "hi"}).encode(),
        headers={"Content-Type": "application/json"})
    body = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert body == {"result": {"echo": {"msg": "hi"}}}

    health = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/-/healthz", timeout=10).read()
    assert health == b"ok"


def test_autoscaling_up(serve_cluster):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1, "upscale_delay_s": 0.5})
    class Slow:
        def __call__(self, _):
            time.sleep(1.5)
            return "done"

    handle = serve.run(Slow.bind(), name="slow")
    controller = serve._controller(create=False)

    responses = [handle.remote(None) for _ in range(8)]
    deadline = time.monotonic() + 30
    peak = 1
    while time.monotonic() < deadline:
        deps = rt.get(controller.get_deployments.remote("slow"), timeout=10)
        peak = max(peak, deps[0]["num_replicas"])
        if peak >= 2:
            break
        time.sleep(0.5)
    assert peak >= 2, "autoscaler never scaled up"
    for r in responses:
        assert r.result(timeout=60) == "done"


def test_delete_app(serve_cluster):
    @serve.deployment
    def noop(x):
        return x

    serve.run(noop.bind(), name="tmp")
    controller = serve._controller(create=False)
    assert "tmp" in rt.get(controller.list_applications.remote(), timeout=10)
    serve.delete("tmp")
    assert "tmp" not in rt.get(controller.list_applications.remote(),
                               timeout=10)


def test_streaming_handle(serve_cluster):
    """Replica generator -> DeploymentResponseGenerator (token streaming,
    ref: serve response streaming over ObjectRefGenerator)."""
    @serve.deployment
    class Tokens:
        def __call__(self, n):
            for i in range(n):
                yield f"tok{i}"

    h = serve.run(Tokens.bind(), name="stream_app")
    items = list(h.options(stream=True).remote(5))
    assert items == [f"tok{i}" for i in range(5)]
    # non-streaming call on the same deployment still works via a fresh
    # deployment (generators need stream=True)
    items2 = list(h.options(stream=True).remote(3))
    assert items2 == ["tok0", "tok1", "tok2"]


def test_streaming_http_sse(serve_cluster):
    """SSE response through the proxy (?stream=1)."""
    port = serve.start(http_port=0)

    @serve.deployment
    class Chat:
        async def __call__(self, payload):
            import asyncio

            for i in range(int(payload["n"])):
                await asyncio.sleep(0.001)
                yield {"token": i}

    serve.run(Chat.bind(), name="chat")
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/chat?stream=1&n=4", method="GET")
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        body = resp.read().decode()
    events = [json.loads(line[len("data: "):])
              for line in body.splitlines() if line.startswith("data: ")]
    assert events == [{"token": i} for i in range(4)]


def test_multiplexed_models(serve_cluster):
    """Model multiplexing: per-replica LRU loading + model-id context
    (ref: serve/multiplex.py)."""
    @serve.deployment
    class ModelHost:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return f"model-{model_id}"

        async def __call__(self, payload):
            mid = serve.get_multiplexed_model_id()
            model = await self.get_model(mid)
            return {"model": model, "loads": list(self.loads),
                    "payload": payload}

    h = serve.run(ModelHost.bind(), name="mux")
    r1 = h.options(multiplexed_model_id="a").remote(1).result(timeout=30)
    assert r1["model"] == "model-a" and r1["loads"] == ["a"]
    # repeat request: cached, no second load
    r2 = h.options(multiplexed_model_id="a").remote(2).result(timeout=30)
    assert r2["loads"] == ["a"]
    # two more models evict the LRU ("a")
    h.options(multiplexed_model_id="b").remote(3).result(timeout=30)
    r4 = h.options(multiplexed_model_id="c").remote(4).result(timeout=30)
    assert r4["loads"] == ["a", "b", "c"]
    r5 = h.options(multiplexed_model_id="a").remote(5).result(timeout=30)
    assert r5["loads"] == ["a", "b", "c", "a"]  # reloaded after eviction


def test_yaml_config_deploy(serve_cluster, tmp_path):
    """Declarative YAML deploy with per-deployment overrides (ref:
    serve/schema.py + `serve deploy`)."""
    import sys
    import textwrap

    mod = tmp_path / "my_serve_app.py"
    mod.write_text(textwrap.dedent("""
        from ray_tpu import serve

        @serve.deployment
        class Echo:
            def __init__(self, prefix="e"):
                self.prefix = prefix

            def __call__(self, x):
                return f"{self.prefix}:{x}"

        def builder(prefix="built"):
            return Echo.bind(prefix)

        app = Echo.bind("static")
    """))
    sys.path.insert(0, str(tmp_path))
    try:
        yaml_cfg = f"""
applications:
  - name: yaml_static
    import_path: my_serve_app:app
  - name: yaml_built
    import_path: my_serve_app:builder
    args: {{prefix: cfg}}
    deployments:
      - name: Echo
        num_replicas: 2
"""
        cfg_file = tmp_path / "serve.yaml"
        cfg_file.write_text(yaml_cfg)
        handles = serve.deploy_config(str(cfg_file))
        assert handles["yaml_static"].remote("x").result(
            timeout=30) == "static:x"
        assert handles["yaml_built"].remote("y").result(
            timeout=30) == "cfg:y"
        import ray_tpu as rt2
        from ray_tpu.serve import _controller

        deps = rt2.get(_controller().get_deployments.remote("yaml_built"),
                       timeout=30)
        assert deps[0]["num_replicas"] == 2
    finally:
        sys.path.remove(str(tmp_path))


def test_rolling_replace_drains_inflight(serve_cluster):
    """Version replace must not kill replicas mid-request: old replicas
    leave the routing table immediately but drain in-flight requests
    (ADVICE r2 #5; ref deployment_state.py graceful replica stop)."""
    import threading

    @serve.deployment
    class Slow:
        def __init__(self, version):
            self.version = version

        def __call__(self, delay):
            time.sleep(delay)
            return self.version

    h1 = serve.run(Slow.bind("v1"), name="roll")
    assert h1.remote(0).result(timeout=30) == "v1"

    result = {}

    def long_request():
        try:
            result["value"] = h1.remote(3.0).result(timeout=60)
        except Exception as e:  # pragma: no cover - the failure mode
            result["error"] = repr(e)

    t = threading.Thread(target=long_request)
    t.start()
    time.sleep(0.5)  # request is in flight on the v1 replica

    h2 = serve.run(Slow.bind("v2"), name="roll")
    # new requests land on the new version
    assert h2.remote(0).result(timeout=30) == "v2"
    # the in-flight v1 request completes instead of dying with the replica
    t.join(timeout=60)
    assert result.get("value") == "v1", result


def test_router_sees_cross_handle_load(serve_cluster):
    """The controller-reported replica load reaches fresh handles, so
    pow-2 isn't blind to other clients' traffic (ADVICE r2 weak #5; ref:
    replica_scheduler/common.py queue-length cache)."""
    @serve.deployment(num_replicas=2)
    class Sleeper:
        def __call__(self, t):
            time.sleep(t)
            return "ok"

    h = serve.run(Sleeper.bind(), name="loadapp")
    pending = [h.remote(2.5) for _ in range(3)]
    time.sleep(1.5)  # reconcile tick collects replica stats

    h2 = serve.get_app_handle("loadapp")
    h2._refresh(force=True)
    assert sum(h2._load.values()) >= 1.0, h2._load
    assert all(p.result(timeout=30) == "ok" for p in pending)


def test_grpc_ingress_unary_and_stream(serve_cluster):
    """Generic gRPC data plane (ref analog: serve gRPC proxy)."""
    import grpc

    port = serve.start_grpc(grpc_port=0)

    @serve.deployment
    class Echo:
        def __call__(self, payload):
            if isinstance(payload, dict) and payload.get("n"):
                def gen():
                    for i in range(int(payload["n"])):
                        yield {"tok": i}
                return gen()
            return {"echo": payload}

    serve.run(Echo.bind(), name="gapp")
    chan = grpc.insecure_channel(f"127.0.0.1:{port}")
    predict = chan.unary_unary(
        "/rayt.serve.Serve/Predict",
        request_serializer=lambda b: b, response_deserializer=lambda b: b)
    resp = json.loads(predict(
        json.dumps({"app": "gapp", "payload": "hi"}).encode(), timeout=30))
    assert resp == {"echo": "hi"}

    stream = chan.unary_stream(
        "/rayt.serve.Serve/PredictStream",
        request_serializer=lambda b: b, response_deserializer=lambda b: b)
    items = [json.loads(m) for m in stream(
        json.dumps({"app": "gapp", "payload": {"n": 3}}).encode(),
        timeout=30)]
    assert items == [{"tok": 0}, {"tok": 1}, {"tok": 2}]

    # unknown app -> NOT_FOUND
    try:
        predict(json.dumps({"app": "nope", "payload": 1}).encode(),
                timeout=30)
        raise AssertionError("expected NOT_FOUND")
    except grpc.RpcError as e:
        assert e.code() == grpc.StatusCode.NOT_FOUND
    chan.close()


# ------------------------------------------------ rolling updates (round 4)
def test_rolling_update_zero_dropped_requests(serve_cluster):
    """Deploy v2 of an app under continuous traffic: every request
    succeeds, answers switch from v1 to v2, and the routing table never
    goes empty (ref: deployment_state.py rolling update)."""
    import threading

    @serve.deployment(num_replicas=2)
    class V:
        def __call__(self):
            return "v1"

    handle = serve.run(V.bind(), name="roll")
    assert handle.remote().result(timeout=30) == "v1"

    results: list = []
    errors: list = []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                results.append(handle.remote().result(timeout=30))
            except Exception as e:
                errors.append(repr(e))

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.5)

        @serve.deployment(num_replicas=2)
        class V:  # noqa: F811  — same deployment name, new code
            def __call__(self):
                return "v2"

        serve.run(V.bind(), name="roll")
        # wait until traffic is fully on v2
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            recent = results[-10:]
            if len(recent) == 10 and all(r == "v2" for r in recent):
                break
            time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, f"dropped requests during rolling update: {errors[:3]}"
    assert "v1" in results and "v2" in results
    assert results[-1] == "v2"
    # no response from any third version / garbage
    assert set(results) <= {"v1", "v2"}


def test_replica_health_probe_replaces_unhealthy(serve_cluster):
    """A replica whose check_health starts failing is killed and replaced
    by the reconcile loop; requests keep succeeding (ref:
    deployment_state.py health checks)."""

    @serve.deployment(num_replicas=1, health_check_period_s=0.5,
                      health_check_timeout_s=2.0,
                      health_check_failure_threshold=2)
    class Flaky:
        def __init__(self):
            import os

            self.pid = os.getpid()
            self.calls = 0

        def check_health(self):
            self.calls += 1
            if self.calls >= 2:
                raise RuntimeError("replica went bad")

        def __call__(self):
            return self.pid

    handle = serve.run(Flaky.bind(), name="flaky")
    first_pid = handle.remote().result(timeout=30)
    # the probe loop must replace the replica (new process, new pid)
    deadline = time.monotonic() + 60
    new_pid = first_pid
    while time.monotonic() < deadline:
        try:
            new_pid = handle.remote().result(timeout=30)
            if new_pid != first_pid:
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert new_pid != first_pid, "unhealthy replica was never replaced"
