"""Multi-agent RL (VERDICT r5 item #7; ref analogs:
rllib/env/multi_agent_env_runner.py, core/rl_module/multi_rl_module.py,
examples MultiAgentCartPole): policy mapping, per-policy batching
through the shared learner stack, per-policy metrics."""

import numpy as np
import pytest

import ray_tpu as rt


@pytest.fixture
def rl_cluster(local_cluster):
    yield local_cluster


def test_multi_agent_env_lockstep():
    from ray_tpu.rl import MultiAgentCartPole

    env = MultiAgentCartPole(num_envs=4, seed=0, num_agents=3)
    obs = env.reset(0)
    assert set(obs) == {"agent_0", "agent_1", "agent_2"}
    assert obs["agent_0"].shape == (4, 4)
    actions = {a: np.zeros(4, np.int32) for a in env.agent_ids}
    obs2, rew, term, trunc, final = env.step(actions)
    assert all(rew[a].shape == (4,) for a in env.agent_ids)
    # independent streams: different seeds per agent -> different states
    assert not np.allclose(obs2["agent_0"], obs2["agent_1"])


def test_policy_mapping_groups_agents(rl_cluster):
    """4 agents -> 2 policies; each policy's runner batch carries BOTH
    its agents' streams (per-module batching)."""
    import cloudpickle

    from ray_tpu.rl.module import MLPModuleConfig
    from ray_tpu.rl.multi_agent import MultiAgentEnvRunner
    from ray_tpu.rl import module as rlm
    import jax

    cfgs = {"even": MLPModuleConfig(observation_size=4, num_actions=2,
                                    hidden=(16,)),
            "odd": MLPModuleConfig(observation_size=4, num_actions=2,
                                   hidden=(16,))}
    mapping = lambda aid: "even" if int(aid[-1]) % 2 == 0 else "odd"
    runner = MultiAgentEnvRunner(
        "MultiAgentCartPole", 4, 0, cloudpickle.dumps(cfgs),
        cloudpickle.dumps(mapping),
        cloudpickle.dumps({"num_agents": 4}))
    assert runner.policy_agents == {"even": ["agent_0", "agent_2"],
                                    "odd": ["agent_1", "agent_3"]}
    params = {p: rlm.init_params(c, jax.random.PRNGKey(0))
              for p, c in cfgs.items()}
    runner.set_weights(params)
    out = runner.sample(8)["policies"]
    # 2 agents x 4 envs = 8 streams per policy
    assert out["even"]["obs"].shape == (8, 8, 4)
    assert out["odd"]["rewards"].shape == (8, 8)
    assert out["even"]["last_value"].shape == (8,)


def test_multi_agent_ppo_learns_two_policies(rl_cluster):
    """2-policy MultiAgentCartPole learns: both policies' mean returns
    improve over training, with per-policy metrics reported."""
    from ray_tpu.rl import MultiAgentPPOConfig

    algo = MultiAgentPPOConfig(
        env="MultiAgentCartPole",
        env_config={"num_agents": 2},
        num_env_runners=2,
        num_envs_per_runner=8,
        rollout_fragment_length=64,
        policies={"agent_0": {}, "agent_1": {"hidden": (32, 32)}},
        policy_mapping_fn=lambda aid: aid,
        minibatch_size=512,
        seed=0).build()
    try:
        first = algo.train()
        assert set(first["policies"]) == {"agent_0", "agent_1"}
        assert "learner/loss" in first["policies"]["agent_0"]
        last = first
        for _ in range(11):
            last = algo.train()
        # both policies independently beat their starting return
        for p in ("agent_0", "agent_1"):
            assert (last["policies"][p]["episode_return_mean"]
                    > first["policies"][p]["episode_return_mean"]), (
                p, first["policies"][p], last["policies"][p])
        assert last["num_env_steps_sampled"] > 0
    finally:
        algo.stop()
