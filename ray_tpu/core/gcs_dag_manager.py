"""GCS dag manager — the cluster-wide compiled-DAG state store (the
execution-plane sibling of gcs_task_manager.py / gcs_object_manager.py).

The compiled-DAG driver registers each DAG at compile time (edge
topology: producer/consumer endpoints, channel kind, ring geometry) and
every participating process — the driver and each actor loop — publishes
per-channel stat snapshots (ticks, bytes, ring occupancy, write/read
block time, slot-pin holds, gc-nudges, DCN credit window) on the
``dag_state`` pubsub channel at the report cadence. This module
coalesces them into one record per DAG with per-edge rollups, runs the
STALL WATCHDOG attribution (an edge whose consumer is parked on an
empty ring — or producer on a full one — past the grace window is
flagged; the blocked side's peer is cross-referenced against the GCS
actor table, so "runner died → ring stalled" names the dead peer), and
answers server-side filtered queries for `rayt list dags` / `rayt dag
<id>`, the dashboard DAGs tab, and state_api.list_dags — with the same
memory bound + per-job oldest-first eviction + dropped accounting
contract as its siblings.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Optional

# pubsub channel the driver/actor-loop dag reports ride (defined here,
# next to its consumer; gcs.py re-exports it beside its siblings)
CH_DAGS = "dag_state"

# per-edge throughput history kept for the dashboard sparklines:
# (ts, ticks, bytes, occupancy) points at the report cadence
_HISTORY_POINTS = 60


def _endpoint(raw) -> dict:
    raw = raw or {}
    return {"actor": raw.get("actor", ""),
            "label": raw.get("label", "driver")}


class GcsDagManager:
    def __init__(self, max_dags: int = 500, stall_grace_s: float = 5.0,
                 actor_state: Optional[Callable[[str], Optional[str]]] = None,
                 event_cb: Optional[Callable] = None):
        self.max_dags = max_dags
        self.stall_grace_s = stall_grace_s
        # actor hex -> lifecycle state string ("ALIVE"/"DEAD"/...), or
        # None when unknown; the GCS server wires its actor table in
        self._actor_state = actor_state or (lambda _hex: None)
        # cluster-event emitter for stall flag/clear TRANSITIONS (the
        # GCS server wires its event manager in): cb(kind, message,
        # severity, job_id, data) — called only when the flag CHANGES,
        # never per report
        self._event_cb = event_cb
        # dag_id -> record; insertion-ordered so per-job eviction finds
        # a job's oldest record cheaply via the index
        self._dags: dict[str, dict] = {}
        # job_hex -> insertion-ordered set of its dag ids
        self._by_job: dict[str, dict[str, None]] = {}
        self._dropped_per_job: collections.Counter = collections.Counter()
        # (dag_id, channel key) -> edge id, for report routing
        self._chan_edge: dict[tuple[str, str], str] = {}
        self._reports_ingested = 0
        # incrementally-maintained stall count: every stall set/clear
        # routes through _set_stall, so the per-report hot path never
        # rescans the whole store
        self._num_stalled = 0
        self._last_stalled_emitted = -1
        # metric records derived from report deltas, drained by the GCS
        # publish handler into the metrics store (this process has no
        # core worker — same raw-record pattern as the node manager)
        self._metric_records: list[dict] = []

    # ------------------------------------------------------------ ingest
    def ingest(self, report: dict):
        if not isinstance(report, dict):
            return
        self._reports_ingested += 1
        kind = report.get("kind")
        if kind == "register":
            self._ingest_register(report)
        elif kind == "report":
            self._ingest_report(report)
        elif kind == "teardown":
            self._ingest_teardown(report)

    def _ingest_register(self, report: dict):
        dag_id = report.get("dag_id") or ""
        if not dag_id:
            return
        job = report.get("job_id") or ""
        ts = float(report.get("ts", 0.0))
        edges: dict[str, dict] = {}
        for e in report.get("edges") or ():
            edge_id = e.get("edge") or f"e{len(edges)}"
            edges[edge_id] = {
                "edge": edge_id,
                "producer": _endpoint(e.get("producer")),
                "consumer": _endpoint(e.get("consumer")),
                "kind": e.get("kind", "shm"),
                # shm|dcn beneath a device edge (same as kind otherwise)
                "transport": e.get("transport", e.get("kind", "shm")),
                # the kind this edge WANTS (co-located device/shm; see
                # core/placement.py preferred_kind_summary)
                "preferred": e.get("preferred", ""),
                "channel": e.get("channel", ""),
                "n_slots": int(e.get("n_slots", 0)),
                "slot_size": int(e.get("slot_size", 0)),
                "role": e.get("role", "edge"),   # input | edge | output
                # producer-side cumulatives (device_arrays counts the
                # jax.Array leaves shipped as raw shard bytes on a
                # kind=device edge; stays 0 on host edges)
                "device_arrays": 0,
                "ticks": 0, "bytes": 0, "write_block_s": 0.0,
                # consumer-side cumulatives
                "reads": 0, "read_block_s": 0.0, "occupancy": 0,
                "pinned_slots": 0, "gc_nudges": 0, "credits": None,
                # live in-progress block durations (stall inputs)
                "write_blocked_s": 0.0, "read_blocked_s": 0.0,
                "stall": None,
                "last_report_ts": 0.0,
                "history": collections.deque(maxlen=_HISTORY_POINTS),
            }
            self._chan_edge[(dag_id, e.get("channel", ""))] = edge_id
        self._dags[dag_id] = {
            "dag_id": dag_id,
            "job_id": job,
            "driver": report.get("driver", ""),
            "state": "RUNNING",
            "created_at": ts,
            "updated_at": ts,
            "torn_down_at": 0.0,
            "channel_kinds": dict(report.get("channel_kinds") or {}),
            # recovery lineage: epoch > 0 marks a recompile-and-resume
            # ring and recovered_from names the dag_id it replaced
            "epoch": int(report.get("epoch", 0)),
            "recovered_from": report.get("recovered_from", ""),
            # placement quality at compile time: fraction of edges on
            # their preferred (co-located) channel kind
            "preferred_kind_ratio": report.get("preferred_kind_ratio"),
            "edges": edges,
        }
        self._by_job.setdefault(job, {})[dag_id] = None
        ratio = report.get("preferred_kind_ratio")
        if ratio is not None:
            from ray_tpu.util.builtin_metrics import \
                dag_preferred_kind_record

            self._metric_records.append(dag_preferred_kind_record(
                dag_id, float(ratio), ts=ts))
        self._maybe_evict()

    def _ingest_report(self, report: dict):
        dag_id = report.get("dag_id") or ""
        rec = self._dags.get(dag_id)
        if rec is None:
            return  # evicted / pre-registration race: drop silently
        ts = float(report.get("ts", 0.0))
        rec["updated_at"] = max(rec["updated_at"], ts)
        for chan, entry in (report.get("channels") or {}).items():
            edge_id = self._chan_edge.get((dag_id, chan))
            edge = rec["edges"].get(edge_id) if edge_id else None
            if edge is None:
                continue
            role = entry.get("role", "")
            if role == "producer":
                d_ticks = max(0, int(entry.get("writes", 0))
                              - edge["ticks"])
                d_bytes = max(0, int(entry.get("bytes_written", 0))
                              - edge["bytes"])
                d_wblock = max(0.0, float(entry.get("write_block_s", 0.0))
                               - edge["write_block_s"])
                edge["ticks"] += d_ticks
                edge["bytes"] += d_bytes
                edge["write_block_s"] += d_wblock
                edge["write_blocked_s"] = float(
                    entry.get("write_blocked_s_now", 0.0))
                if entry.get("credits") is not None:
                    edge["credits"] = int(entry["credits"])
                if entry.get("device_arrays") is not None:
                    edge["device_arrays"] = max(
                        edge["device_arrays"],
                        int(entry["device_arrays"]))
                self._emit_edge_metrics(dag_id, edge_id, ts,
                                        ticks=d_ticks, nbytes=d_bytes,
                                        write_block_s=d_wblock)
                # one history point per producer report (the consumer's
                # report carries the SAME cumulative ticks — appending
                # on both roles would zigzag the dashboard rate series
                # between 0 and 2x and halve the window)
                edge["history"].append((ts, edge["ticks"],
                                        edge["bytes"],
                                        edge["occupancy"]))
            else:  # consumer
                d_reads = max(0, int(entry.get("reads", 0))
                              - edge["reads"])
                d_rblock = max(0.0, float(entry.get("read_block_s", 0.0))
                               - edge["read_block_s"])
                edge["reads"] += d_reads
                edge["read_block_s"] += d_rblock
                edge["read_blocked_s"] = float(
                    entry.get("read_blocked_s_now", 0.0))
                edge["occupancy"] = int(entry.get("occupancy", 0))
                edge["pinned_slots"] = int(entry.get("pinned_slots", 0))
                edge["gc_nudges"] = int(entry.get("gc_nudges", 0))
                self._emit_edge_metrics(dag_id, edge_id, ts,
                                        read_block_s=d_rblock,
                                        occupancy=edge["occupancy"])
            edge["last_report_ts"] = ts
            self._check_stall(rec, edge, ts)
        self._emit_stalled_gauge(ts)

    def _ingest_teardown(self, report: dict):
        rec = self._dags.get(report.get("dag_id") or "")
        if rec is None:
            return
        ts = float(report.get("ts", 0.0))
        rec["state"] = "TORN_DOWN"
        rec["torn_down_at"] = ts
        rec["updated_at"] = max(rec["updated_at"], ts)
        # a torn-down DAG's parked loops are expected, not stalled
        for edge in rec["edges"].values():
            self._set_stall(rec, edge, None)
            edge["write_blocked_s"] = 0.0
            edge["read_blocked_s"] = 0.0
        self._emit_stalled_gauge(ts)

    # ----------------------------------------------------- stall watchdog
    def _set_stall(self, rec: dict, edge: dict, stall):
        """Every stall set/clear routes here so _num_stalled stays an
        O(1) incrementally-maintained count — and so flag TRANSITIONS
        (not per-report re-flags) land in the cluster event log with
        the watchdog's attribution."""
        had = edge["stall"] is not None
        edge["stall"] = stall
        if stall is not None and not had:
            self._num_stalled += 1
            self._emit_event(
                "dag_stall", "WARNING", rec, edge,
                f"dag {rec['dag_id'][:12]} edge {edge['edge']} "
                f"{stall['blocked']}-blocked {stall['blocked_s']:.1f}s; "
                f"culprit {stall['culprit']}"
                + (" (peer DEAD)" if stall.get("dead_peer") else ""),
                stall)
        elif stall is None and had:
            self._num_stalled -= 1
            self._emit_event(
                "dag_stall_cleared", "INFO", rec, edge,
                f"dag {rec['dag_id'][:12]} edge {edge['edge']} "
                f"stall cleared", None)

    def _emit_event(self, kind, severity, rec, edge, message, stall):
        if self._event_cb is None:
            return
        try:
            self._event_cb(kind, message, severity, rec["job_id"],
                           {"dag_id": rec["dag_id"],
                            "edge": edge["edge"],
                            **(dict(stall) if stall else {})})
        except Exception:
            pass

    def _check_stall(self, rec: dict, edge: dict, ts: float):
        """Attribution: a consumer parked on an EMPTY ring points at the
        producer (nothing arriving); a producer parked on a FULL ring
        points at the consumer (nothing draining). The culprit peer's
        liveness comes from the GCS actor table — a DEAD peer turns an
        opaque stall into a one-line diagnosis."""
        if rec["state"] != "RUNNING":
            self._set_stall(rec, edge, None)  # straggler after teardown
            return
        blocked_kind = None
        blocked_s = 0.0
        if edge["read_blocked_s"] >= self.stall_grace_s:
            blocked_kind, blocked_s = "read", edge["read_blocked_s"]
            culprit = edge["producer"]
        elif edge["write_blocked_s"] >= self.stall_grace_s:
            blocked_kind, blocked_s = "write", edge["write_blocked_s"]
            culprit = edge["consumer"]
        else:
            self._set_stall(rec, edge, None)
            return
        peer_state = (self._actor_state(culprit["actor"])
                      if culprit["actor"] else None)
        self._set_stall(rec, edge, {
            "blocked": blocked_kind,
            "blocked_s": round(blocked_s, 3),
            "culprit": culprit["label"],
            "culprit_actor": culprit["actor"],
            "culprit_state": peer_state or "",
            "dead_peer": (culprit["actor"]
                          if peer_state == "DEAD" else ""),
            "detected_at": ts,
        })

    def num_stalled_edges(self) -> int:
        return self._num_stalled

    # ---------------------------------------------------- derived metrics
    def _emit_edge_metrics(self, dag_id: str, edge_id: str, ts: float, *,
                           ticks: int = 0, nbytes: int = 0,
                           write_block_s: float = 0.0,
                           read_block_s: float = 0.0,
                           occupancy: Optional[int] = None):
        from ray_tpu.util.builtin_metrics import dag_edge_metric_records

        self._metric_records.extend(dag_edge_metric_records(
            dag_id, edge_id, ticks=ticks, nbytes=nbytes,
            write_block_s=write_block_s, read_block_s=read_block_s,
            occupancy=occupancy, ts=ts))

    def _emit_stalled_gauge(self, ts: float):
        """Gauge record on CHANGE only: reports arrive at ~1/s per
        participating process cluster-wide, and an unchanged count per
        report would flood the metrics store for nothing."""
        if self._num_stalled == self._last_stalled_emitted:
            return
        from ray_tpu.util.builtin_metrics import dag_stalled_gauge_record

        self._last_stalled_emitted = self._num_stalled
        self._metric_records.append(
            dag_stalled_gauge_record(self._num_stalled, ts=ts))

    def drain_metric_records(self) -> list[dict]:
        out, self._metric_records = self._metric_records, []
        return out

    # ----------------------------------------------------- memory bound
    def _maybe_evict(self):
        """Per-job eviction under the global cap: the job holding the
        most DAG records gives up its OLDEST one (same fairness contract
        as GcsTaskManager / GcsObjectManager)."""
        evicted = False
        while len(self._dags) > self.max_dags:
            victim_job = max(self._by_job,
                             key=lambda j: len(self._by_job[j]))
            job_dags = self._by_job[victim_job]
            dag_id = next(iter(job_dags))
            del job_dags[dag_id]
            if not job_dags:
                del self._by_job[victim_job]
            self._drop(dag_id)
            self._dropped_per_job[victim_job] += 1
            evicted = True
        if evicted:
            # an evicted record may have carried stall flags; the
            # register that triggered eviction drains this record
            self._emit_stalled_gauge(time.time())

    def _drop(self, dag_id: str):
        rec = self._dags.pop(dag_id, None)
        if rec is None:
            return
        for edge in rec["edges"].values():
            self._set_stall(rec, edge, None)  # keep _num_stalled exact
            self._chan_edge.pop((dag_id, edge["channel"]), None)

    def on_job_finished(self, job_hex: str):
        """The exiting driver owned the job's DAGs: drop their records
        (regular freeing, not eviction — no dropped accounting)."""
        dropped = list(self._by_job.pop(job_hex, ()))
        for dag_id in dropped:
            self._drop(dag_id)
        if dropped:
            # a crashed driver's stall-flagged records just vanished:
            # without this the gauge would stay frozen at its last
            # nonzero value forever (the caller drains the record)
            self._emit_stalled_gauge(time.time())

    # ------------------------------------------------------------ queries
    @staticmethod
    def _edge_view(edge: dict) -> dict:
        out = {k: v for k, v in edge.items() if k != "history"}
        out["stall"] = dict(edge["stall"]) if edge["stall"] else None
        out["history"] = [list(p) for p in edge["history"]]
        return out

    def _record_view(self, rec: dict) -> dict:
        stalled = [e["edge"] for e in rec["edges"].values() if e["stall"]]
        ticks = max((e["ticks"] for e in rec["edges"].values()),
                    default=0)
        return {
            "dag_id": rec["dag_id"], "job_id": rec["job_id"],
            "driver": rec["driver"], "state": rec["state"],
            "created_at": rec["created_at"],
            "updated_at": rec["updated_at"],
            "torn_down_at": rec["torn_down_at"],
            "channel_kinds": dict(rec["channel_kinds"]),
            "epoch": rec.get("epoch", 0),
            "recovered_from": rec.get("recovered_from", ""),
            "preferred_kind_ratio": rec.get("preferred_kind_ratio"),
            "num_edges": len(rec["edges"]),
            "ticks": ticks,
            "bytes": sum(e["bytes"] for e in rec["edges"].values()),
            "stalled_edges": stalled,
            "edges": [self._edge_view(e) for e in rec["edges"].values()],
        }

    def _iter_filtered(self, job_id=None, dag_id=None, stalled_only=False):
        if dag_id is not None:
            rec = self._dags.get(dag_id)
            source = (rec,) if rec is not None else ()
        elif job_id is not None:
            ids = self._by_job.get(job_id, ())
            source = (self._dags[d] for d in ids if d in self._dags)
        else:
            source = iter(self._dags.values())
        for rec in source:
            if stalled_only and not any(e["stall"]
                                        for e in rec["edges"].values()):
                continue
            yield rec

    def list(self, *, job_id: Optional[str] = None,
             dag_id: Optional[str] = None, stalled_only: bool = False,
             limit: int = 100) -> dict:
        """Filtered DAG records, newest-first, with truncation + per-job
        dropped accounting (mirrors GcsTaskManager.list)."""
        matched = list(self._iter_filtered(job_id, dag_id, stalled_only))
        matched.reverse()  # insertion order -> newest first
        limit = max(0, limit or 0)  # <= 0 means unlimited
        truncated = max(0, len(matched) - limit) if limit else 0
        return {
            "dags": [self._record_view(r)
                     for r in (matched[:limit] if limit else matched)],
            "total": len(matched),
            "truncated": truncated,
            "dropped": self.dropped_counts(job_id),
        }

    def summarize(self, *, job_id: Optional[str] = None) -> dict:
        """Rollup for `rayt summary`-style surfaces: DAG counts by
        state, edge/tick/byte totals, blocked-time totals, and every
        currently-stalled edge with its attribution."""
        by_state: collections.Counter = collections.Counter()
        totals = {"dags": 0, "edges": 0, "ticks": 0, "bytes": 0,
                  "write_block_s": 0.0, "read_block_s": 0.0,
                  "gc_nudges": 0, "stalled_edges": 0}
        stalls: list[dict] = []
        for rec in self._iter_filtered(job_id):
            totals["dags"] += 1
            by_state[rec["state"]] += 1
            # same definition as _record_view: a DAG's tick count is
            # the max over its edges (summing would count one logical
            # tick once per pipeline stage)
            totals["ticks"] += max(
                (e["ticks"] for e in rec["edges"].values()), default=0)
            for e in rec["edges"].values():
                totals["edges"] += 1
                totals["bytes"] += e["bytes"]
                totals["write_block_s"] += e["write_block_s"]
                totals["read_block_s"] += e["read_block_s"]
                totals["gc_nudges"] += e["gc_nudges"]
                if e["stall"]:
                    totals["stalled_edges"] += 1
                    stalls.append({
                        "dag_id": rec["dag_id"], "edge": e["edge"],
                        "producer": e["producer"]["label"],
                        "consumer": e["consumer"]["label"],
                        **e["stall"]})
        totals["write_block_s"] = round(totals["write_block_s"], 3)
        totals["read_block_s"] = round(totals["read_block_s"], 3)
        return {
            "by_state": dict(by_state),
            "totals": totals,
            "stalls": stalls,
            "dropped": self.dropped_counts(job_id),
        }

    def dropped_counts(self, job_id: Optional[str] = None) -> dict:
        if job_id is not None:
            return {job_id: self._dropped_per_job.get(job_id, 0)}
        return dict(self._dropped_per_job)

    def num_dags(self) -> int:
        return len(self._dags)

    def raw(self, dag_id: str) -> Optional[dict]:
        """Internal record by exact dag id — the placement plane's
        measured-edge-bytes input (core/placement.py advise_dag); stays
        a reference, callers must not mutate."""
        return self._dags.get(dag_id)
