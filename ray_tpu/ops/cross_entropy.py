"""Softmax cross entropy over large vocabularies.

Computed in fp32 without materializing [batch*seq, vocab] probabilities
twice: logsumexp + gather, which XLA fuses tightly. Supports masking
(ignore index) for padded batches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          ignore_index: int = -100
                          ) -> tuple[jax.Array, jax.Array]:
    """logits: [..., vocab] (any dtype, accumulated fp32); labels: [...]
    int32. Returns (mean_loss, num_valid_tokens)."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (lse - picked) * valid
    n = jnp.maximum(valid.sum(), 1)
    return nll.sum() / n, valid.sum()
