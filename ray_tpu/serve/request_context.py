"""Serve request-path observability plumbing: the request id minted at
the ingress (echoed as ``X-Rayt-Request-Id``), the batched publisher
that ships partial request records to the GCS serve manager on the
``serve_state`` channel, and the contextvar bridge that lets the
LLMEngine stamp its phase timings (prefill / TTFT / TPOT / occupancy)
into the request being handled without threading a handle through
every engine call.

Publishing mirrors util/metrics.py's _Batcher: records buffer in a
process-local list and a flusher on the core worker's IO loop ships one
publish per ``metrics_flush_interval_s`` — the request hot path costs a
lock + list append, never an RPC. When no cluster is connected (or
``RAYT_SERVE_REQUESTS_ENABLED=0``) records drop at the door.
"""

from __future__ import annotations

import asyncio
import contextvars
import threading
import time
import uuid
import weakref
from typing import Optional

from ray_tpu.core.gcs_serve_manager import CH_SERVE


def mint_request_id() -> str:
    """A fresh request id (uuid4 hex): minted once at the ingress, it
    rides the call envelope into handle -> replica -> engine and keys
    the coalesced GCS record."""
    return uuid.uuid4().hex


def recording_enabled() -> bool:
    """Config gate, resolved per call so RAYT_CONFIG_JSON-spawned
    processes and tests see live values (get_config caches)."""
    try:
        from ray_tpu._internal.config import get_config

        return bool(get_config().serve_requests_enabled)
    except Exception:
        return False


# ------------------------------------------------------------- recorder
class _ServeRecorder:
    """Process-local buffer of partial request / engine records with a
    periodic flush to the GCS serve channel (same lifecycle handling as
    util/metrics.py's _Batcher: the pending flush is presumed dead when
    aged out or spawned on a previous core worker)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buf: list[dict] = []
        self._scheduled = False
        self._scheduled_at = 0.0
        self._scheduled_cw: Optional[weakref.ref] = None
        self._interval: float | None = None

    def publish(self, record: dict):
        if not recording_enabled():
            return
        cw = self._core_worker()
        if cw is None:
            return
        with self._lock:
            self._buf.append(record)
            now = time.monotonic()
            stale = max(2.0, 2.0 * (self._interval or 0.0) + 0.5)
            schedule = (not self._scheduled
                        or now - self._scheduled_at > stale
                        or self._scheduled_cw is None
                        or self._scheduled_cw() is not cw)
            if schedule:
                self._scheduled = True
                self._scheduled_at = now
                self._scheduled_cw = weakref.ref(cw)
        if schedule:
            self._spawn_flush(cw)

    @staticmethod
    def _core_worker():
        try:
            from ray_tpu.core.object_ref import get_core_worker

            cw = get_core_worker()
            if cw is None or cw.gcs is None:
                return None
            return cw
        except Exception:
            return None

    def _spawn_flush(self, cw):
        try:
            cw._spawn_from_thread(self._flush_later(cw))
        except Exception:
            with self._lock:
                self._scheduled = False

    async def _flush_later(self, cw):
        from ray_tpu._internal.config import get_config

        try:
            self._interval = get_config().metrics_flush_interval_s
            await asyncio.sleep(self._interval)
        except Exception:
            pass
        with self._lock:
            records, self._buf = self._buf, []
        try:
            if records and cw.gcs is not None:
                await cw.gcs.publish(CH_SERVE, records)
        except Exception:
            pass  # best-effort: dropped on GCS hiccup / shutdown
        resume = False
        with self._lock:
            if self._buf:
                resume = True  # records raced in during the publish
                self._scheduled_at = time.monotonic()
            else:
                self._scheduled = False
        if resume:
            try:
                cw._spawn(self._flush_later(cw))  # already on the IO loop
            except Exception:
                with self._lock:
                    self._scheduled = False


_recorder = _ServeRecorder()


def publish_record(record: dict):
    """Best-effort publish of one partial record (proxy/replica side);
    never raises on the request path."""
    try:
        _recorder.publish(record)
    except Exception:
        pass


# ------------------------------------------- engine phase-stamp bridge
# the replica sets this around the user-callable invocation; the
# LLMEngine picks it up in generate() and stamps phase timings into it
# from the engine-loop executor threads (plain dict writes — the GIL
# makes the individual float/int stores atomic, and the replica only
# reads after the handler returns)
_request_obs: contextvars.ContextVar[Optional[dict]] = \
    contextvars.ContextVar("rayt_serve_request_obs", default=None)


def current_request_obs() -> Optional[dict]:
    """Inside a replica handler: the mutable observation dict for the
    request being handled (None when recording is off or the call
    didn't come through an instrumented ingress)."""
    return _request_obs.get()


def _set_request_obs(obs: Optional[dict]):
    return _request_obs.set(obs)


def _reset_request_obs(token):
    _request_obs.reset(token)


def engine_section(obs: Optional[dict]) -> Optional[dict]:
    """Fold an engine-stamped observation dict into the record's
    ``engine`` section (replica side, after the handler returns).
    Returns None when the engine never touched the request."""
    if not obs or "gen_start" not in obs:
        return None
    first = obs.get("first_token")
    last = obs.get("last_token", first)
    tokens = int(obs.get("tokens", 0))
    out = {
        "queue_s": obs.get("queue_s"),
        "prefill_s": obs.get("prefill_s"),
        "prefill_chunks": int(obs.get("prefill_chunks", 0)),
        "tokens": tokens,
        "decode_steps": int(obs.get("decode_steps", 0)),
    }
    if first is not None:
        out["ttft_s"] = first - obs["gen_start"]
        if last is not None and last > first and tokens > 1:
            out["decode_s"] = last - first
            out["tpot_s"] = (last - first) / (tokens - 1)
    steps = out["decode_steps"]
    if steps:
        out["occupancy_mean"] = obs.get("occupancy_sum", 0.0) / steps
    for k in ("prefix_cache", "prefix_hit_tokens", "kv_handoff_bytes",
              "kv_handoff_edge"):
        if obs.get(k) is not None:
            out[k] = obs[k]
    if obs.get("pool"):
        # one half of a disagg prefill/decode pair: this side's
        # structural zeros (decode counters on the prefill record,
        # chunk counts on the decode record) would clobber the other
        # half's real values at GCS coalesce time — merge order is
        # flush-cadence luck, so ship only the phases this pool ran
        out = {k: v for k, v in out.items() if v not in (None, 0)}
    return out
