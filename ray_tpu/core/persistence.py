"""GCS snapshot persistence backends (ref analog:
src/ray/gcs/store_client/ — in_memory_store_client vs
redis_store_client.h:107).

The reference achieves head HA by backing GCS tables with an EXTERNAL
Redis so a restarted head (anywhere) rebuilds its view. The TPU-native
analog keeps the same split without a Redis dependency: a
`SnapshotBackend` port with two adapters —

* :class:`FileSnapshotBackend` — local file + content-addressed blob
  dir (the existing single-box layout, byte-compatible with old
  snapshots);
* :class:`RemoteSnapshotBackend` — blocking bridge to a standalone
  :class:`SnapshotStoreServer` process reachable over the cluster RPC
  substrate (`rayt://host:port`), which survives head death so the head
  can restart on a DIFFERENT machine and reload.

Select by address: `gcs_persist_path = "/path/snap.pkl"` or
`"rayt://10.0.0.5:6410"`. The store server runs via
`python -m ray_tpu.core.store_main --dir /data/gcs --port 6410`.
"""

from __future__ import annotations

import os
from typing import Optional

from ray_tpu._internal.logging_utils import setup_logger

logger = setup_logger("persistence")

REMOTE_SCHEME = "rayt://"


class SnapshotBackend:
    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def put_if_absent(self, key: str, value: bytes) -> None:
        if not self.exists(key):
            self.put(key, value)

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _safe_name(key: str) -> str:
    # keys are "snapshot" or "blobs/<sha256>"; no traversal allowed
    name = key.replace("/", "_")
    if name != os.path.basename(name) or name.startswith("."):
        raise ValueError(f"bad snapshot key {key!r}")
    return name


class FileSnapshotBackend(SnapshotBackend):
    """Single-box layout: `base` is the snapshot file, blobs live in
    `base + '.blobs/<digest>'` (unchanged from the pre-backend code, so
    existing snapshots keep loading)."""

    def __init__(self, base: str):
        self.base = base

    def _path(self, key: str) -> str:
        if key == "snapshot":
            return self.base
        if key.startswith("blobs/"):
            return os.path.join(self.base + ".blobs", key[len("blobs/"):])
        raise ValueError(f"unknown snapshot key {key!r}")

    def put(self, key: str, value: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
            # fsync BEFORE the rename: os.replace alone is atomic
            # against a process crash but not a host crash — the rename
            # can hit disk before the data, leaving a torn snapshot
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))


class RemoteSnapshotBackend(SnapshotBackend):
    """Sync facade over the async RPC client: snapshot IO happens off
    the GCS event loop (executor thread / process start-stop), so each
    call blocks on a private IO loop the way CoreWorker's sync API
    does.

    Store-server restarts are expected (it is a plain process on a
    different box), so every call retries with backoff and redials the
    connection on transport errors. Only after the retry budget is
    exhausted does the error surface — and `failure_listener` (wired by
    the GCS server to a WARNING cluster event) fires so operators learn
    persistence is degraded even though the head keeps running."""

    MAX_ATTEMPTS = 4
    BACKOFF_S = 0.2      # doubles per attempt: 0.2, 0.4, 0.8

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        from ray_tpu._internal.rpc import EventLoopThread, connect

        self._host, self._port = host, port
        self._io = EventLoopThread(name="rayt-snap-store")
        self._timeout = timeout_s
        self._conn = self._io.run(connect(host, port), timeout_s)
        # called (exc, method) after the retry budget is exhausted
        self.failure_listener = None

    def _redial(self):
        from ray_tpu._internal.rpc import connect

        try:
            self._io.run(self._conn.close(), 2)
        except Exception:
            pass
        self._conn = self._io.run(connect(self._host, self._port),
                                  self._timeout)

    def _call(self, method: str, arg):
        import time as _time

        delay = self.BACKOFF_S
        last: Exception | None = None
        for attempt in range(self.MAX_ATTEMPTS):
            try:
                if self._conn is None:
                    self._redial()
                return self._io.run(self._conn.call(method, arg),
                                    self._timeout)
            except Exception as e:
                last = e
                self._conn = None   # force a redial next attempt
                if attempt < self.MAX_ATTEMPTS - 1:
                    logger.warning(
                        "snapshot store %s failed (%r), retrying in "
                        "%.1fs (%d/%d)", method, e, delay, attempt + 1,
                        self.MAX_ATTEMPTS)
                    _time.sleep(delay)
                    delay *= 2
        logger.error("snapshot store %s failed after %d attempts: %r",
                     method, self.MAX_ATTEMPTS, last)
        if self.failure_listener is not None:
            try:
                self.failure_listener(last, method)
            except Exception:
                pass
        raise last

    def put(self, key: str, value: bytes) -> None:
        self._call("store_put", (key, value))

    def get(self, key: str) -> Optional[bytes]:
        return self._call("store_get", key)

    def exists(self, key: str) -> bool:
        return bool(self._call("store_exists", key))

    def close(self) -> None:
        try:
            if self._conn is not None:
                self._io.run(self._conn.close(), 5)
        except Exception:
            pass
        self._io.stop()


def make_backend(persist_path: Optional[str]) -> Optional[SnapshotBackend]:
    if not persist_path:
        return None
    if persist_path.startswith(REMOTE_SCHEME):
        hostport = persist_path[len(REMOTE_SCHEME):]
        host, _, port = hostport.partition(":")
        return RemoteSnapshotBackend(host, int(port))
    return FileSnapshotBackend(persist_path)


class SnapshotStoreServer:
    """Standalone durable KV for GCS snapshots — the Redis-role process.
    Values land in `dir` via atomic replace; restart-safe; shared by
    successive head incarnations."""

    def __init__(self, data_dir: str):
        from ray_tpu._internal.rpc import RpcServer

        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.server = RpcServer()
        self.server.add_service(self)
        self.port: Optional[int] = None

    def _path(self, key: str) -> str:
        return os.path.join(self.data_dir, _safe_name(key))

    def rpc_store_put(self, conn, arg):
        key, value = arg
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(bytes(value))
            # durability is this process's whole job: data must be on
            # disk before the rename commits it (host-crash safety)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return True

    def rpc_store_get(self, conn, key):
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def rpc_store_exists(self, conn, key):
        return os.path.exists(self._path(key))

    def rpc_store_ping(self, conn, arg=None):
        return True

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self.port = await self.server.start(host, port)
        logger.info("snapshot store listening on %s:%s (dir=%s)",
                    host, self.port, self.data_dir)
        return self.port

    async def stop(self):
        await self.server.stop()
