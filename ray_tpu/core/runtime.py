"""Driver-side cluster bootstrap: init/shutdown (ref analog:
python/ray/_private/worker.py:1275 `init` + _private/{node,services}.py
process launching)."""

from __future__ import annotations

import atexit
import json
import os
import subprocess
import sys
import time

from ray_tpu._internal.config import get_config
from ray_tpu._internal.ids import JobID, NodeID
from ray_tpu._internal.logging_utils import setup_logger
from ray_tpu.core.common import Address
from ray_tpu.core.core_worker import CoreWorker

logger = setup_logger("runtime")

_global: "RuntimeContext | None" = None


class RuntimeContext:
    def __init__(self):
        self.head_proc: subprocess.Popen | None = None
        self.core_worker: CoreWorker | None = None
        self.gcs_address: Address | None = None
        self.nm_address: Address | None = None
        self.head_node_id: NodeID | None = None
        self.job_id: JobID | None = None
        self.owns_cluster = False


def _detect_default_resources(num_cpus, resources):
    out = dict(resources or {})
    if num_cpus is None:
        num_cpus = os.cpu_count() or 1
    out.setdefault("CPU", float(num_cpus))
    if "TPU" not in out:
        # TPU autodetect (ref analog: _private/accelerators/tpu.py:70):
        # GKE env -> GCE metadata -> devfs; advertises slice-typed
        # resources (TPU-v5e-8, TPU-v5e-8-head on worker 0) so slice
        # gang-scheduling works with no flags.
        from ray_tpu._internal.accelerators import detect_tpu_slice

        info = detect_tpu_slice(
            use_metadata=os.environ.get("RAYT_DISABLE_GCE_METADATA") != "1")
        if info is not None:
            for k, v in info.resources().items():
                out.setdefault(k, v)
    out.setdefault("memory", float(_system_memory_bytes()))
    return out


def _system_memory_bytes() -> int:
    try:
        import psutil

        return psutil.virtual_memory().total
    except Exception:
        return 8 << 30


def is_initialized() -> bool:
    return _global is not None


def get_runtime_context() -> RuntimeContext:
    if _global is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global


def init(address: str | None = None, *, num_cpus: float | None = None,
         resources: dict | None = None, log_to_driver: bool = True,
         ignore_reinit_error: bool = False, **kwargs) -> RuntimeContext:
    global _global
    if _global is not None:
        if ignore_reinit_error:
            return _global
        raise RuntimeError("ray_tpu already initialized (pass "
                           "ignore_reinit_error=True to tolerate)")
    ctx = RuntimeContext()
    if address is None:
        from ray_tpu._internal.spawn import child_env, fast_python_argv

        total = _detect_default_resources(num_cpus, resources)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = child_env(pkg_root)
        env["RAYT_CONFIG_JSON"] = get_config().to_json()
        ctx.head_proc = subprocess.Popen(
            fast_python_argv("ray_tpu.core.head_main")
            + ["--resources", json.dumps(total)],
            stdout=subprocess.PIPE, env=env, text=True)
        line = ctx.head_proc.stdout.readline()
        if not line:
            raise RuntimeError("head process failed to start")
        info = json.loads(line)
        ctx.gcs_address = Address("127.0.0.1", info["gcs_port"])
        ctx.nm_address = Address("127.0.0.1", info["nm_port"])
        ctx.head_node_id = NodeID.from_hex(info["node_id"])
        ctx.owns_cluster = True
    else:
        host, port = address.split(":")
        ctx.gcs_address = Address(host, int(port))
        # attach: discover the head node manager via GCS
        import asyncio

        from ray_tpu.core.gcs import GcsClient

        async def _discover():
            gcs = await GcsClient.connect(ctx.gcs_address)
            nodes = await gcs.get_all_nodes()
            await gcs.close()
            return nodes

        nodes = asyncio.run(_discover())
        head = next((n for n in nodes if n.labels.get("head")), nodes[0])
        ctx.nm_address = head.address
        ctx.head_node_id = head.node_id

    ctx.job_id = JobID.random()
    os.environ["RAYT_JOB_ID"] = ctx.job_id.hex()
    cw = CoreWorker(mode="driver", job_id=ctx.job_id,
                    gcs_address=ctx.gcs_address,
                    node_address=ctx.nm_address,
                    node_id=ctx.head_node_id)
    cw.connect_cluster()
    cw.io.run(cw.gcs.conn.call("register_job", (ctx.job_id, {"driver_pid": os.getpid()})))
    ctx.core_worker = cw
    _global = ctx
    atexit.register(shutdown)
    return ctx


def shutdown():
    global _global
    ctx = _global
    if ctx is None:
        return
    _global = None
    try:
        if ctx.core_worker is not None:
            try:
                ctx.core_worker.io.run(
                    ctx.core_worker.gcs.conn.call("finish_job", ctx.job_id),
                    timeout=2)
            except Exception:
                pass
            ctx.core_worker.shutdown()
    finally:
        if ctx.owns_cluster and ctx.head_proc is not None:
            ctx.head_proc.terminate()
            try:
                ctx.head_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                ctx.head_proc.kill()
    try:
        atexit.unregister(shutdown)
    except Exception:
        pass
