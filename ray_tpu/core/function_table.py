"""Function table: ship task/actor code once, not once per submit.

Ref analog: the reference's function manager exports each remote
function/class to GCS KV exactly once per job and workers import it by
id (python/ray/_private/function_manager.py:58). Here the id rides the
TaskSpec and the blob travels at most once per worker connection
(piggybacked on the first push), with GCS KV as the durable miss path —
a spillback/retry landing on a fresh worker whose owner-connection never
saw the blob still recovers.

Owner side (:class:`FunctionTable`):
 * ``dumps_code`` runs ONCE per (function, job) — the dominant
   per-submit cost before this table (~30us of cloudpickle per task).
 * function_id = job hex + blake2b(blob): content-addressed, so a
   redefined function (new bytecode/closure) gets a new id while a
   re-decorated identical function reuses the cached entry.
 * every blob is published to GCS KV (``fn_table`` namespace) once, in
   the background for tasks and synchronously for actor creation (the
   spec reaches the executing worker via GCS, never over an owner
   connection that could piggyback the blob).

Worker side (:class:`FunctionCache`):
 * loaded code cached by id in an LRU (``fn_cache_size`` entries) with
   job-scoped eviction (``evict_job``) so one job's churn cannot pin
   another job's code out of the cache forever.
 * blobs arriving piggybacked on a push are staged by the RPC handler
   (before the executor hop) so later same-connection pushes that omit
   the blob always find either the staged bytes or the loaded entry.
 * a miss (LRU eviction, fresh worker after spillback/retry) fetches the
   blob from GCS KV with a short retry — the owner's background publish
   is racy only within the first few milliseconds of a job.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from collections import OrderedDict

import cloudpickle

# GCS KV namespace holding code blobs keyed by function_id
KV_NAMESPACE = "fn_table"


class FunctionTable:
    """Owner-side registry: function object -> (function_id, blob)."""

    def __init__(self):
        # weak-keyed so a dropped user function doesn't pin its blob here
        # (the worker LRU + GCS KV own the rest of the lifetime)
        self._by_fn: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self._blobs: dict[str, bytes] = {}
        self._kv_pushed: set[str] = set()
        self._lock = threading.Lock()
        self.dumps_count = 0  # regression hook: serializations performed

    def register(self, fn, job_id) -> tuple[str, bytes]:
        """Return (function_id, blob) for `fn`, serializing at most once
        per (function, job)."""
        jh = job_id.hex()
        try:
            cached = self._by_fn.get(fn)
        except TypeError:  # unhashable/unweakrefable callable
            cached = None
        if cached is not None and cached[0] == jh:
            return cached[1], cached[2]
        from ray_tpu._internal.serialization import dumps_code

        blob = dumps_code(fn)
        self.dumps_count += 1
        fid = jh + ":" + hashlib.blake2b(blob, digest_size=16).hexdigest()
        with self._lock:
            self._blobs[fid] = blob
        try:
            self._by_fn[fn] = (jh, fid, blob)
        except TypeError:
            pass
        return fid, blob

    def blob_for(self, fid: str) -> bytes | None:
        with self._lock:
            return self._blobs.get(fid)

    def needs_kv_push(self, fid: str) -> bool:
        """True exactly once per id — the caller owns the actual put."""
        with self._lock:
            if fid in self._kv_pushed:
                return False
            self._kv_pushed.add(fid)
            return True

    def kv_push_failed(self, fid: str):
        """A background publish died: let a later submit retry it."""
        with self._lock:
            self._kv_pushed.discard(fid)


class FunctionCache:
    """Worker-side loaded-code cache: function_id -> callable/class."""

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._loaded: OrderedDict[str, tuple[str, object]] = OrderedDict()
        self._staged: dict[str, bytes] = {}  # blobs awaiting first load
        self._lock = threading.Lock()
        self.misses = 0  # KV fetches (regression hook)

    def stage_blob(self, fid: str, blob: bytes):
        """Record a piggybacked blob before the executor hop (cheap, on
        the RPC loop) so a later push omitting the blob can't race the
        first one's load."""
        with self._lock:
            if fid not in self._loaded:
                self._staged[fid] = blob

    def resolve(self, fid: str, job_hex: str, fetch_blob):
        """Return the loaded function/class for `fid`. ``fetch_blob`` is
        the KV miss path: called (off the RPC loop) only when neither the
        LRU nor the staged blobs have the id."""
        with self._lock:
            hit = self._loaded.get(fid)
            if hit is not None:
                self._loaded.move_to_end(fid)
                return hit[1]
            blob = self._staged.pop(fid, None)
        if blob is None:
            self.misses += 1
            blob = fetch_blob(fid)
            if blob is None:
                raise RuntimeError(
                    f"function blob {fid!r} not in the GCS function "
                    "table (owner gone before publishing?)")
        fn = cloudpickle.loads(blob)
        with self._lock:
            self._loaded[fid] = (job_hex, fn)
            self._loaded.move_to_end(fid)
            while len(self._loaded) > self.capacity:
                self._loaded.popitem(last=False)
        return fn

    def evict_job(self, job_hex: str):
        """Drop every entry a finished job loaded (driver disconnect /
        job teardown): pooled workers outlive jobs."""
        with self._lock:
            for fid in [f for f, (jh, _) in self._loaded.items()
                        if jh == job_hex]:
                del self._loaded[fid]
            for fid in [f for f in self._staged if f.startswith(job_hex + ":")]:
                del self._staged[fid]

    def __len__(self):
        with self._lock:
            return len(self._loaded)
