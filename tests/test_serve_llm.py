"""TP-sharded LLM serving (BASELINE config #5 shape, on the CPU mesh):
engine batching/parity + Serve deployment streaming (ref analog:
serve/_private/replica.py:750 + response streaming; the engine itself is
TPU-native, no reference equivalent)."""

import asyncio

import jax
import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.models import llama
from ray_tpu.serve.llm import LLMEngine


def _collect(engine, tokens, **kw):
    async def run():
        return [t async for t in engine.generate(tokens, **kw)]
    return asyncio.run(run())


def test_engine_greedy_matches_unbatched_decode():
    """Batched left-padded generation must equal a plain single-sequence
    greedy decode with the same params."""
    eng = LLMEngine("debug", tp=2, max_batch=4)
    cfg = eng.cfg
    prompt = [5, 9, 11, 42, 7]
    got = _collect(eng, prompt, max_new_tokens=8)

    # plain reference decode: no padding, batch 1
    params = jax.device_get(eng.params)
    cache = llama.init_kv_cache(cfg, 1, max_len=cfg.max_seq_len)
    toks = np.asarray([prompt], np.int32)
    logits, cache = llama.decode_step(params, cache, toks, cfg)
    want = []
    for _ in range(8):
        nxt = int(np.argmax(np.asarray(logits)[0]))
        want.append(nxt)
        logits, cache = llama.decode_step(
            params, cache, np.asarray([[nxt]], np.int32), cfg)
    assert got == want


def test_engine_batches_concurrent_requests():
    eng = LLMEngine("debug", tp=2, max_batch=4)

    async def run():
        outs = await asyncio.gather(*[
            _agen_list(eng.generate([3 + i, 8, 1], max_new_tokens=5))
            for i in range(3)])
        return outs

    outs = asyncio.run(run())
    assert all(len(o) == 5 for o in outs)
    # continuous batching: all three requests decode in SHARED steps.
    # Each needs 4 decode steps after its prefill token; run serially
    # that would be 12 — shared slots need far fewer (admission skew can
    # cost a couple of extra steps).
    assert eng.prefills == 3
    assert eng.batches <= 8
    # different prompts may produce different streams; each is deterministic
    again = _collect(eng, [3, 8, 1], max_new_tokens=5)
    assert again == outs[0]


async def _agen_list(agen):
    return [t async for t in agen]


def test_engine_respects_per_request_lengths_and_eos():
    eng = LLMEngine("debug", tp=2, max_batch=4)

    async def run():
        a, b = await asyncio.gather(
            _agen_list(eng.generate([1, 2, 3], max_new_tokens=2)),
            _agen_list(eng.generate([9, 9], max_new_tokens=7)))
        return a, b

    a, b = asyncio.run(run())
    assert len(a) == 2
    assert len(b) == 7


def test_late_request_joins_mid_decode():
    """The continuous-batching contract: a request arriving while another
    is mid-generation starts decoding within ~1 step — it never waits
    for the in-flight request to drain its token budget."""
    eng = LLMEngine("debug", tp=2, max_batch=4)

    async def run():
        first = asyncio.ensure_future(
            _agen_list(eng.generate([1, 2, 3], max_new_tokens=60)))
        # let the first request get well into decode
        while eng.batches < 5:
            await asyncio.sleep(0.01)
        steps_before = eng.batches
        late = await _agen_list(eng.generate([7, 7], max_new_tokens=3))
        steps_for_late = eng.batches - steps_before
        first_done = first.done()
        out_first = await first
        return out_first, late, steps_for_late, first_done

    out_first, late, steps_for_late, first_done = asyncio.run(run())
    assert len(out_first) == 60
    assert len(late) == 3
    # 3 tokens = 1 prefill token + 2 decode steps; a drain-first engine
    # would burn ~55 steps before the late request emitted anything
    assert steps_for_late <= 6
    # and the first request was still decoding when the late one finished
    assert not first_done


def test_llm_serve_app_streams_tokens(local_cluster):
    try:
        app = __import__("ray_tpu.serve.llm", fromlist=["llm_app"]).llm_app(
            "debug", tp=2, max_batch=4)
        h = serve.run(app, name="llm")
        items = list(h.options(stream=True).remote(
            {"tokens": [4, 8, 15], "max_new_tokens": 6}))
        assert len(items) == 6
        assert all(isinstance(d["token"], int) for d in items)
    finally:
        serve.shutdown()


@pytest.mark.slow  # 3 engine builds (~35s of traces); tier-1 keeps the
# base-decode parity test, the LoRA-specific path gates in the slow lane
def test_engine_applies_lora_adapter():
    """An engine whose params carry a "lora" subtree shards and applies
    the adapter for real: a zero-init adapter (B=0) matches the base
    decode bit-for-bit, a nonzero adapter changes the stream."""
    from ray_tpu.models import lora as lora_mod

    base_eng = LLMEngine("debug", tp=2, max_batch=2, seed=0)
    base = jax.device_get(base_eng.params)
    cfg = base_eng.cfg
    adapter = lora_mod.init_lora_params(
        cfg, lora_mod.LoraConfig(rank=4, alpha=cfg.lora_alpha),
        jax.random.PRNGKey(7))
    eng = LLMEngine("debug", tp=2, max_batch=2,
                    params={**base, "lora": adapter}, seed=0)
    prompt = [5, 9, 11, 42, 7]
    want = _collect(base_eng, prompt, max_new_tokens=8)
    got = _collect(eng, prompt, max_new_tokens=8)
    assert got == want  # B=0: adapter is an exact no-op
    # a trained (nonzero-B) adapter must change the decode
    adapter2 = jax.tree.map(
        lambda a: a + 0.5 if a.ndim and a.shape[-1] != 4 else a, adapter)
    eng2 = LLMEngine("debug", tp=2, max_batch=2,
                     params={**base, "lora": adapter2}, seed=0)
    assert _collect(eng2, prompt, max_new_tokens=8) != want


@pytest.mark.slow  # cluster + three per-adapter engine builds
def test_multiplexed_lora_service_e2e(local_cluster):
    """lora_llm_app: adapters route by multiplexed model id, stream
    adapter-tagged tokens, and the per-replica LRU bounds residents
    (third adapter evicts the LRU one)."""
    try:
        from ray_tpu.serve.llm import lora_llm_app

        app = lora_llm_app("debug", tp=2, max_batch=2,
                           max_adapters_per_replica=2)
        h = serve.run(app, name="lora")

        def gen(adapter):
            return list(h.options(
                multiplexed_model_id=adapter, stream=True).remote(
                {"tokens": [4, 8, 15], "max_new_tokens": 4}))

        a = gen("ad-a")
        assert len(a) == 4 and all(d["adapter"] == "ad-a" for d in a)
        b = gen("ad-b")
        assert len(b) == 4 and all(d["adapter"] == "ad-b" for d in b)
        # different adapters may produce different streams; repeat
        # traffic for one adapter is deterministic (cached engine)
        assert gen("ad-a") == a
        # residency reported through replica stats; 2-adapter LRU means
        # a third adapter evicts one
        h._refresh(force=True)
        replica = h._replicas[0]
        models = rt.get(replica.get_stats.remote(), timeout=30)["models"]
        assert sorted(models) == ["ad-a", "ad-b"]
        gen("ad-c")
        models = rt.get(replica.get_stats.remote(), timeout=30)["models"]
        assert len(models) == 2 and "ad-c" in models
    finally:
        serve.shutdown()


def test_chunked_prefill_interleaves_with_decode():
    """A long-prompt admission must not stall active decode streams for
    the whole prompt: prefill advances one CHUNK per engine round, with
    decode steps in between (vLLM-style chunked prefill)."""
    eng = LLMEngine("debug", tp=2, max_batch=4, max_seq_len=1024,
                    prompt_buckets=(32, 512), prefill_chunk=64)

    async def run():
        # record the engine's prefill progress at each first-stream token
        # so we can assert tokens kept flowing DURING the chunked prefill
        chunks_at_token = []

        async def consume_first():
            out = []
            async for t in eng.generate([1, 2, 3], max_new_tokens=40):
                out.append(t)
                chunks_at_token.append(eng.prefill_chunks)
            return out

        first = asyncio.ensure_future(consume_first())
        while eng.batches < 3:
            await asyncio.sleep(0.01)
        # inject a LONG prompt (bucket 512 -> 8 chunks of 64)
        long_prompt = list(range(1, 301))
        late = await _agen_list(eng.generate(long_prompt,
                                             max_new_tokens=3))
        out_first = await first
        return out_first, late, chunks_at_token

    out_first, late, chunks_at_token = asyncio.run(run())
    assert len(out_first) == 40
    assert len(late) == 3
    # 300 real tokens in a 512 bucket, chunk 64: pad chunks are skipped
    # (192 of 212 pad tokens), leaving ceil(320/64) = 5 chunk rounds
    assert eng.prefill_chunks == 5
    # the actual interleaving claim: first-stream tokens were emitted
    # while the long prefill was mid-flight (a drain-prefill-first engine
    # would show every token at chunks 0 or 5)
    assert any(0 < c < 5 for c in chunks_at_token), chunks_at_token
    # parity: the chunked path produces the same tokens as monolithic
    eng2 = LLMEngine("debug", tp=2, max_batch=4, max_seq_len=1024,
                     prompt_buckets=(32, 512), prefill_chunk=0, seed=0)
    eng3 = LLMEngine("debug", tp=2, max_batch=4, max_seq_len=1024,
                     prompt_buckets=(32, 512), prefill_chunk=64, seed=0)
    prompt = [5, 9, 11, 42, 7] * 30  # 150 tokens -> bucket 512
    mono = _collect(eng2, prompt, max_new_tokens=6)
    chunked = _collect(eng3, prompt, max_new_tokens=6)
    assert mono == chunked
